"""Pooling (reference: python/paddle/nn/functional/pooling.py). All lower to
lax.reduce_window."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dtype as _dtype_mod

from ...ops import dispatch
from ...ops._factory import ensure_tensor
from .conv import _padding_for, _tuple_n


def _window(nd_spatial, data_format, ks, st):
    if data_format.startswith("NC"):
        dims = (1, 1) + ks
        strides = (1, 1) + st
        spatial_off = 2
    else:
        dims = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
        spatial_off = 1
    return dims, strides, spatial_off


def _full_pad(pairs, nd, spatial_off):
    full = [(0, 0)] * nd
    for i, p in enumerate(pairs):
        full[spatial_off + i] = tuple(p)
    return full


def max_pool2d(
    x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
    data_format="NCHW", name=None,
):
    return _max_pool(x, kernel_size, stride, padding, return_mask, ceil_mode, data_format, 2)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    return _max_pool(x, kernel_size, stride, padding, return_mask, ceil_mode, "NCL", 1)


def max_pool3d(
    x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
    data_format="NCDHW", name=None,
):
    return _max_pool(x, kernel_size, stride, padding, return_mask, ceil_mode, data_format, 3)


def _max_pool(x, kernel_size, stride, padding, return_mask, ceil_mode, data_format, nsp):
    x = ensure_tensor(x)
    ks = _tuple_n(kernel_size, nsp)
    st = _tuple_n(stride if stride is not None else kernel_size, nsp)
    pairs = _padding_for(padding, nsp)
    dims, strides, off = _window(nsp, data_format, ks, st)

    def fn(a):
        if isinstance(pairs, str):
            pad_arg = pairs
        else:
            pad_arg = _full_pad(pairs, a.ndim, off)
        neg = -jnp.inf if _dtype_mod.is_float_raw(a.dtype) else np.iinfo(np.dtype(a.dtype)).min
        return jax.lax.reduce_window(a, neg, jax.lax.max, dims, strides, pad_arg)

    out = dispatch.apply(fn, x, op_name="max_pool")
    if return_mask:
        idx = dispatch.apply_nondiff(
            lambda a: _argmax_pool(a, dims, strides, pairs, off, nsp), x)
        return out, idx
    return out


def _argmax_pool(a, dims, strides, pairs, off, nsp=None):
    # int32 indices carried through a variadic reduce_window: a float
    # carrier (old scheme) silently downcasts to f32 without x64 and
    # loses exactness past 2^24 elements.  NC-leading layouts only need
    # plane-local indices, so the guard bounds the largest index actually
    # carried, not the global size.
    per_plane = nsp is not None and off == 2
    plane = int(np.prod(a.shape[off:off + nsp])) if per_plane else None
    max_index = (plane if per_plane else a.size) - 1
    if max_index > np.iinfo(np.int32).max:
        raise ValueError(
            "max_pool return_mask: mask indices up to "
            f"{max_index} do not fit int32")
    if per_plane:
        # paddle's mask is the index WITHIN each (N, C) plane (h*W + w),
        # not the global flat index — and the spatial dims are
        # innermost/contiguous.  Built with broadcast_to so no index ever
        # exceeds the plane size (taken BEFORE the reduce).
        flat_idx = jnp.broadcast_to(
            jnp.arange(plane, dtype=jnp.int32).reshape(a.shape[off:]),
            a.shape)
    else:
        flat_idx = jnp.arange(a.size, dtype=jnp.int32).reshape(a.shape)

    # pack (value, index): use a reduce over tuples via argmax trick
    def select(x1, x2):
        v1, i1 = x1
        v2, i2 = x2
        pick = v1 >= v2
        return jnp.where(pick, v1, v2), jnp.where(pick, i1, i2)

    pad_arg = "VALID" if isinstance(pairs, str) and pairs == "VALID" else (
        pairs if isinstance(pairs, str) else _full_pad(pairs, a.ndim, off)
    )
    neg = jnp.finfo(a.dtype).min if _dtype_mod.is_float_raw(a.dtype) else np.iinfo(np.dtype(a.dtype)).min
    vals, idx = jax.lax.reduce_window(
        (a, flat_idx),
        (jnp.asarray(neg, a.dtype), jnp.asarray(-1, jnp.int32)),
        select,
        dims,
        strides,
        pad_arg,
    )
    return idx.astype(jnp.int64)


def avg_pool2d(
    x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
    divisor_override=None, data_format="NCHW", name=None,
):
    return _avg_pool(x, kernel_size, stride, padding, exclusive, divisor_override, data_format, 2)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    return _avg_pool(x, kernel_size, stride, padding, exclusive, None, "NCL", 1)


def avg_pool3d(
    x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
    divisor_override=None, data_format="NCDHW", name=None,
):
    return _avg_pool(x, kernel_size, stride, padding, exclusive, divisor_override, data_format, 3)


def _avg_pool(x, kernel_size, stride, padding, exclusive, divisor_override, data_format, nsp):
    x = ensure_tensor(x)
    ks = _tuple_n(kernel_size, nsp)
    st = _tuple_n(stride if stride is not None else kernel_size, nsp)
    pairs = _padding_for(padding, nsp)
    dims, strides, off = _window(nsp, data_format, ks, st)

    def fn(a):
        pad_arg = pairs if isinstance(pairs, str) else _full_pad(pairs, a.ndim, off)
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, dims, strides, pad_arg)
        if divisor_override:
            return s / divisor_override
        if exclusive and not isinstance(pairs, str):
            ones = jnp.ones_like(a)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides, pad_arg)
            return s / cnt
        return s / float(np.prod(ks))

    return dispatch.apply(fn, x, op_name="avg_pool")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    os = _tuple_n(output_size, 2)

    def fn(a):
        if data_format == "NHWC":
            # route through the NCHW body (two transposes fold into the
            # surrounding program under XLA)
            a = jnp.transpose(a, (0, 3, 1, 2))
        n, c, h, w = a.shape
        oh, ow = os
        a5 = a.reshape(n, c, oh, h // oh, ow, w // ow) if h % oh == 0 and w % ow == 0 else None
        if a5 is not None:
            out = a5.mean(axis=(3, 5))
        else:
            # general: mean over variable windows
            out = jnp.stack(
                [
                    jnp.stack(
                        [
                            a[:, :, (i * h) // oh : ((i + 1) * h + oh - 1) // oh,
                              (j * w) // ow : ((j + 1) * w + ow - 1) // ow].mean(axis=(2, 3))
                            for j in range(ow)
                        ],
                        axis=-1,
                    )
                    for i in range(oh)
                ],
                axis=-2,
            )
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return dispatch.apply(fn, x, op_name="adaptive_avg_pool2d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    x = ensure_tensor(x)
    os = _tuple_n(output_size, 2)

    def fn(a):
        n, c, h, w = a.shape
        oh, ow = os
        if h % oh == 0 and w % ow == 0:
            return a.reshape(n, c, oh, h // oh, ow, w // ow).max(axis=(3, 5))
        return jnp.stack(
            [
                jnp.stack(
                    [
                        a[:, :, (i * h) // oh : ((i + 1) * h + oh - 1) // oh,
                          (j * w) // ow : ((j + 1) * w + ow - 1) // ow].max(axis=(2, 3))
                        for j in range(ow)
                    ],
                    axis=-1,
                )
                for i in range(oh)
            ],
            axis=-2,
        )

    if not return_mask:
        return dispatch.apply(fn, x, op_name="adaptive_max_pool2d")

    def both_fn(a):
        # ONE pass over the windows produces value and index together
        # (the value gathered at the argmax keeps the max's gradient)
        n, c, h, w = a.shape
        oh, ow = os
        val_rows, idx_rows = [], []
        for i in range(oh):
            vr, ir = [], []
            for j in range(ow):
                hs, he = (i * h) // oh, ((i + 1) * h + oh - 1) // oh
                ws, we = (j * w) // ow, ((j + 1) * w + ow - 1) // ow
                win = a[:, :, hs:he, ws:we].reshape(n, c, -1)
                flat = jnp.argmax(win, axis=-1)
                vr.append(jnp.take_along_axis(
                    win, flat[..., None], axis=-1)[..., 0])
                wy = hs + flat // (we - ws)
                wx = ws + flat % (we - ws)
                ir.append(wy * w + wx)           # per-(N,C)-plane index
            val_rows.append(jnp.stack(vr, -1))
            idx_rows.append(jnp.stack(ir, -1))
        return (jnp.stack(val_rows, -2),
                jnp.stack(idx_rows, -2).astype(jnp.int64))

    return dispatch.apply(both_fn, x, op_name="adaptive_max_pool2d")


def adaptive_avg_pool1d(x, output_size, name=None):
    x = ensure_tensor(x)
    os = int(output_size)

    def fn(a):
        n, c, l = a.shape
        if l % os == 0:
            return a.reshape(n, c, os, l // os).mean(axis=3)
        return jnp.stack(
            [a[:, :, (i * l) // os : ((i + 1) * l + os - 1) // os].mean(axis=2) for i in range(os)],
            axis=-1,
        )

    return dispatch.apply(fn, x, op_name="adaptive_avg_pool1d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    x = ensure_tensor(x)
    os = int(output_size)

    def fn(a):
        n, c, l = a.shape
        if l % os == 0:
            return a.reshape(n, c, os, l // os).max(axis=3)
        return jnp.stack(
            [a[:, :, (i * l) // os : ((i + 1) * l + os - 1) // os].max(axis=2) for i in range(os)],
            axis=-1,
        )

    return dispatch.apply(fn, x, op_name="adaptive_max_pool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """reference phi unpool: scatter pooled values back to the positions
    recorded by max_pool2d(return_mask=True) (per-(N,C)-plane h*W+w
    indices); everything else is zero."""
    if data_format != "NCHW":
        raise NotImplementedError("max_unpool2d supports NCHW")
    x = ensure_tensor(x)
    indices = ensure_tensor(indices)
    ks = _tuple_n(kernel_size, 2)
    st = _tuple_n(stride if stride is not None else kernel_size, 2)
    pd = _tuple_n(padding, 2)
    n_, c_, hh, ww = x._value.shape
    if output_size is not None:
        oh, ow = [int(v) for v in output_size[-2:]]
    else:
        oh = (hh - 1) * st[0] - 2 * pd[0] + ks[0]
        ow = (ww - 1) * st[1] - 2 * pd[1] + ks[1]

    def fn(a, idx):
        n, c = a.shape[0], a.shape[1]
        flat = jnp.zeros((n, c, oh * ow), a.dtype)
        b = jnp.arange(n)[:, None, None]
        ch = jnp.arange(c)[None, :, None]
        vals = a.reshape(n, c, -1)
        ii = idx.reshape(n, c, -1)
        flat = flat.at[b, ch, ii].set(vals)
        return flat.reshape(n, c, oh, ow)

    return dispatch.apply(fn, x, indices, op_name="max_unpool2d")


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """reference phi unpool (1-D form): scatter over the per-(N,C)-plane
    flat indices from max_pool1d(return_mask=True)."""
    if data_format != "NCL":
        raise NotImplementedError("max_unpool1d supports NCL")
    x = ensure_tensor(x)
    indices = ensure_tensor(indices)
    ks = _tuple_n(kernel_size, 1)
    st = _tuple_n(stride if stride is not None else kernel_size, 1)
    pd = _tuple_n(padding, 1)
    n_, c_, ll = x._value.shape
    if output_size is not None:
        ol = int(output_size[-1])
    else:
        ol = (ll - 1) * st[0] - 2 * pd[0] + ks[0]

    def fn(a, idx):
        n, c = a.shape[0], a.shape[1]
        flat = jnp.zeros((n, c, ol), a.dtype)
        b = jnp.arange(n)[:, None, None]
        ch = jnp.arange(c)[None, :, None]
        flat = flat.at[b, ch, idx].set(a)
        return flat

    return dispatch.apply(fn, x, indices, op_name="max_unpool1d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """reference phi unpool3d: scatter pooled values back to the
    positions recorded by max_pool3d(return_mask=True) (per-(N,C)-volume
    d*H*W + h*W + w indices)."""
    if data_format != "NCDHW":
        raise NotImplementedError("max_unpool3d supports NCDHW")
    x = ensure_tensor(x)
    indices = ensure_tensor(indices)
    ks = _tuple_n(kernel_size, 3)
    st = _tuple_n(stride if stride is not None else kernel_size, 3)
    pd = _tuple_n(padding, 3)
    n_, c_, dd, hh, ww = x._value.shape
    if output_size is not None:
        od, oh, ow = [int(v) for v in output_size[-3:]]
    else:
        od = (dd - 1) * st[0] - 2 * pd[0] + ks[0]
        oh = (hh - 1) * st[1] - 2 * pd[1] + ks[1]
        ow = (ww - 1) * st[2] - 2 * pd[2] + ks[2]

    def fn(a, idx):
        n, c = a.shape[0], a.shape[1]
        flat = jnp.zeros((n, c, od * oh * ow), a.dtype)
        b = jnp.arange(n)[:, None, None]
        ch = jnp.arange(c)[None, :, None]
        vals = a.reshape(n, c, -1)
        ii = idx.reshape(n, c, -1)
        flat = flat.at[b, ch, ii].set(vals)
        return flat.reshape(n, c, od, oh, ow)

    return dispatch.apply(fn, x, indices, op_name="max_unpool3d")
