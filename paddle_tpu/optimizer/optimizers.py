"""Concrete optimizers: SGD, Momentum, Adagrad, RMSProp, Adam, AdamW, Adamax,
Lamb (reference: python/paddle/optimizer/*.py; CUDA kernels
phi/kernels/gpu/adam_kernel.cu etc.). Updates are jnp expressions — XLA fuses
each param's update chain; under jit.to_static the whole optimizer fuses into
the train-step program."""
from __future__ import annotations

import jax.numpy as jnp

from ..ops import dispatch
from ..tensor import Tensor
from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adagrad", "Adadelta", "RMSProp", "Adam", "AdamW", "Adamax", "Lamb"]


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _apply_one(self, p, g):
        lr = self._lr_value()
        g_raw = self._decayed_grad(p, g._value.astype(jnp.float32))
        self._write_param(p, (p._value.astype(jnp.float32) - lr * g_raw).astype(p._value.dtype))


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        self._momentum = momentum
        self._nesterov = use_nesterov
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("velocity", p)

    def _apply_one(self, p, g):
        lr = self._lr_value()
        v = self._get_accumulator("velocity", p)
        dispatch.note_read(v)
        g_raw = self._decayed_grad(p, g._value.astype(jnp.float32))
        new_v = self._momentum * v._value + g_raw
        if self._nesterov:
            update = g_raw + self._momentum * new_v
        else:
            update = new_v
        v._set_value(new_v)
        self._write_param(p, (p._value.astype(jnp.float32) - lr * update).astype(p._value.dtype))


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0, name=None):
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("moment", p, fill_value=self._init_acc)

    def _apply_one(self, p, g):
        lr = self._lr_value()
        m = self._get_accumulator("moment", p)
        dispatch.note_read(m)
        g_raw = self._decayed_grad(p, g._value.astype(jnp.float32))
        new_m = m._value + g_raw * g_raw
        m._set_value(new_m)
        self._write_param(
            p,
            (p._value.astype(jnp.float32) - lr * g_raw / (jnp.sqrt(new_m) + self._epsilon)).astype(p._value.dtype),
        )


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None, name=None):
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("momentum", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _apply_one(self, p, g):
        lr = self._lr_value()
        ms = self._get_accumulator("mean_square", p)
        mom = self._get_accumulator("momentum", p)
        dispatch.note_read(ms)
        dispatch.note_read(mom)
        g_raw = self._decayed_grad(p, g._value.astype(jnp.float32))
        new_ms = self._rho * ms._value + (1 - self._rho) * g_raw * g_raw
        if self._centered:
            mg = self._get_accumulator("mean_grad", p)
            dispatch.note_read(mg)
            new_mg = self._rho * mg._value + (1 - self._rho) * g_raw
            denom = jnp.sqrt(new_ms - new_mg * new_mg + self._epsilon)
            mg._set_value(new_mg)
        else:
            denom = jnp.sqrt(new_ms + self._epsilon)
        new_mom = self._momentum * mom._value + lr * g_raw / denom
        ms._set_value(new_ms)
        mom._set_value(new_mom)
        self._write_param(p, (p._value.astype(jnp.float32) - new_mom).astype(p._value.dtype))


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=True, name=None):
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._multi_precision = multi_precision
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _create_accumulators(self, params):
        for p in params:
            # multi_precision: fp32 moments + fp32 master weights for
            # low-precision params (reference multi_precision adam);
            # without it moments live in the PARAM dtype (the reference's
            # plain adam kernel) — the pure-bf16 low-memory regime.
            acc_dt = None if self._multi_precision else p._value.dtype
            self._add_accumulator("moment1", p, dtype=acc_dt)
            self._add_accumulator("moment2", p, dtype=acc_dt)
        self._aux_state[0] = Tensor(jnp.asarray(1.0, jnp.float32))  # beta1^t
        self._aux_state[1] = Tensor(jnp.asarray(1.0, jnp.float32))  # beta2^t
        # fp32 master weights for low-precision params (reference
        # multi_precision adam)
        if self._multi_precision:
            self._master: dict = {}
            hook = getattr(self, "_accumulator_layout_hook", None)
            for p in params:
                if p._value.dtype in (jnp.bfloat16, jnp.float16):
                    m = Tensor(p._value.astype(jnp.float32))
                    if hook is not None:
                        hook(m, p)  # ZeRO: master weights shard like moments
                    self._master[id(p)] = m

    @dispatch.no_grad()
    def step(self):
        params_grads = [(p, g) for p, g in self._collect_params_grads() if g is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        # advance bias-correction powers once per step
        b1p, b2p = self._aux_state[0], self._aux_state[1]
        dispatch.note_read(b1p)
        dispatch.note_read(b2p)
        b1p._set_value(b1p._value * self._beta1)
        b2p._set_value(b2p._value * self._beta2)
        for p, g in params_grads:
            dispatch.note_read(p)
            self._apply_one(p, g)

    def _decayed(self, p, g_raw, pv):
        """Same dispatch as Optimizer._decayed_grad, applied to the
        (possibly master fp32) parameter value: floats add coeff*pv,
        regularizer objects are CALLED (L1Decay adds coeff*sign(pv))."""
        wd = self._weight_decay
        if wd is None:
            return g_raw
        if isinstance(wd, (int, float)):
            return g_raw + float(wd) * pv
        if callable(wd):
            return g_raw + wd(pv)
        return g_raw + getattr(wd, "_coeff", 0.0) * pv

    def _apply_one(self, p, g):
        lr = self._lr_value()
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        dispatch.note_read(m1)
        dispatch.note_read(m2)
        master = getattr(self, "_master", {}).get(id(p))
        if master is not None:
            dispatch.note_read(master)
            pv = master._value
        else:
            pv = p._value.astype(jnp.float32)
        g_raw = self._decayed(p, g._value.astype(jnp.float32), pv)
        new_m1 = self._beta1 * m1._value + (1 - self._beta1) * g_raw
        new_m2 = self._beta2 * m2._value + (1 - self._beta2) * g_raw * g_raw
        b1p = self._aux_state[0]._value
        b2p = self._aux_state[1]._value
        m1_hat = new_m1 / (1 - b1p)
        m2_hat = new_m2 / (1 - b2p)
        new_p = pv - lr * m1_hat / (jnp.sqrt(m2_hat) + self._epsilon)
        m1._set_value(new_m1.astype(m1._value.dtype))
        m2._set_value(new_m2.astype(m2._value.dtype))
        if master is not None:
            master._set_value(new_p)
        self._write_param(p, new_p.astype(p._value.dtype))


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py).

    ``use_fused_kernel=True`` routes the update through the owned Pallas
    multi-tensor kernel (ops/pallas_kernels/fused_adamw.py — the analog of
    the reference's phi/kernels/fusion/fused_adam_kernel.cu): one VMEM
    pass per slab, params/moments aliased in place."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=True, use_fused_kernel=False, name=None):
        # decoupled decay is L2 BY CONSTRUCTION (p *= 1 - lr*coeff):
        # an L1Decay here would silently become L2, so reject it
        # (reference AdamW takes float coefficients only)
        from ..regularizer import L1Decay

        if isinstance(weight_decay, L1Decay):
            raise TypeError(
                "AdamW applies DECOUPLED L2 decay; L1Decay cannot be "
                "expressed here — use Adam(weight_decay=L1Decay(...)) "
                "for L1 regularization")
        self._wd_coeff = (float(weight_decay)
                          if isinstance(weight_decay, (int, float))
                          else getattr(weight_decay, "_coeff", 0.01))
        self._apply_decay_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio
        self._use_fused_kernel = use_fused_kernel
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)

    def _apply_fused(self, p, g, lr, decay):
        import jax as _jax

        from ..ops.pallas_kernels.fused_adamw import fused_adamw_update

        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        dispatch.note_read(m1)
        dispatch.note_read(m2)
        interp = _jax.devices()[0].platform != "tpu"
        new_p, new_m1, new_m2 = fused_adamw_update(
            p._value, g._value, m1._value, m2._value,
            lr, self._aux_state[0]._value, self._aux_state[1]._value,
            beta1=self._beta1, beta2=self._beta2, eps=self._epsilon,
            wd=(self._wd_coeff if decay else 0.0), interpret=interp)
        m1._set_value(new_m1)
        m2._set_value(new_m2)
        self._write_param(p, new_p)

    def _apply_one(self, p, g):
        lr = self._lr_value()
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        decay = True
        if self._apply_decay_fun is not None:
            decay = self._apply_decay_fun(p.name or "")
        master = getattr(self, "_master", {}).get(id(p))
        if self._use_fused_kernel and master is None:
            # fused path covers the single-precision regime (the pure-bf16
            # bench path); master-weight updates stay XLA-composed
            self._apply_fused(p, g, lr, decay)
            return
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        dispatch.note_read(m1)
        dispatch.note_read(m2)
        if master is not None:
            dispatch.note_read(master)
            pv = master._value
        else:
            pv = p._value.astype(jnp.float32)
        g_raw = g._value.astype(jnp.float32)
        new_m1 = self._beta1 * m1._value + (1 - self._beta1) * g_raw
        new_m2 = self._beta2 * m2._value + (1 - self._beta2) * g_raw * g_raw
        b1p = self._aux_state[0]._value
        b2p = self._aux_state[1]._value
        m1_hat = new_m1 / (1 - b1p)
        m2_hat = new_m2 / (1 - b2p)
        new_p = pv
        if decay:
            new_p = new_p * (1.0 - lr * self._wd_coeff)
        new_p = new_p - lr * m1_hat / (jnp.sqrt(m2_hat) + self._epsilon)
        m1._set_value(new_m1.astype(m1._value.dtype))
        m2._set_value(new_m2.astype(m2._value.dtype))
        if master is not None:
            master._set_value(new_p)
        self._write_param(p, new_p.astype(p._value.dtype))


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
        self._aux_state[0] = Tensor(jnp.asarray(1.0, jnp.float32))

    @dispatch.no_grad()
    def step(self):
        params_grads = [(p, g) for p, g in self._collect_params_grads() if g is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        b1p = self._aux_state[0]
        dispatch.note_read(b1p)
        b1p._set_value(b1p._value * self._beta1)
        for p, g in params_grads:
            dispatch.note_read(p)
            self._apply_one(p, g)

    def _apply_one(self, p, g):
        lr = self._lr_value()
        m = self._get_accumulator("moment", p)
        u = self._get_accumulator("inf_norm", p)
        dispatch.note_read(m)
        dispatch.note_read(u)
        g_raw = self._decayed_grad(p, g._value.astype(jnp.float32))
        new_m = self._beta1 * m._value + (1 - self._beta1) * g_raw
        new_u = jnp.maximum(self._beta2 * u._value, jnp.abs(g_raw))
        b1p = self._aux_state[0]._value
        self._write_param(
            p,
            (p._value.astype(jnp.float32) - lr / (1 - b1p) * new_m / (new_u + self._epsilon)).astype(p._value.dtype),
        )
        m._set_value(new_m)
        u._set_value(new_u)


class Lamb(Optimizer):
    """Layer-wise adaptive moments (reference: python/paddle/optimizer/lamb.py)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn
        super().__init__(learning_rate, parameters, None, grad_clip, name)

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
        self._aux_state[0] = Tensor(jnp.asarray(1.0, jnp.float32))
        self._aux_state[1] = Tensor(jnp.asarray(1.0, jnp.float32))

    @dispatch.no_grad()
    def step(self):
        params_grads = [(p, g) for p, g in self._collect_params_grads() if g is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        b1p, b2p = self._aux_state[0], self._aux_state[1]
        dispatch.note_read(b1p)
        dispatch.note_read(b2p)
        b1p._set_value(b1p._value * self._beta1)
        b2p._set_value(b2p._value * self._beta2)
        for p, g in params_grads:
            dispatch.note_read(p)
            self._apply_one(p, g)

    def _apply_one(self, p, g):
        lr = self._lr_value()
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        dispatch.note_read(m1)
        dispatch.note_read(m2)
        pv = p._value.astype(jnp.float32)
        g_raw = g._value.astype(jnp.float32)
        new_m1 = self._beta1 * m1._value + (1 - self._beta1) * g_raw
        new_m2 = self._beta2 * m2._value + (1 - self._beta2) * g_raw * g_raw
        m1_hat = new_m1 / (1 - self._aux_state[0]._value)
        m2_hat = new_m2 / (1 - self._aux_state[1]._value)
        r = m1_hat / (jnp.sqrt(m2_hat) + self._epsilon)
        wd = self._lamb_wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        update = r + wd * pv
        w_norm = jnp.linalg.norm(pv)
        u_norm = jnp.linalg.norm(update)
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        m1._set_value(new_m1.astype(m1._value.dtype))
        m2._set_value(new_m2.astype(m2._value.dtype))
        self._write_param(p, (pv - lr * trust * update).astype(p._value.dtype))


class Adadelta(Optimizer):
    """reference python/paddle/optimizer/adadelta.py (phi adadelta
    kernel): E[g^2] and E[dx^2] running averages; the update needs no
    global learning rate (lr multiplies the final delta for parity)."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        self._epsilon = epsilon
        self._rho = rho
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _apply_one(self, p, g):
        lr = self._lr_value()
        eg = self._get_accumulator("avg_squared_grad", p)
        ex = self._get_accumulator("avg_squared_update", p)
        dispatch.note_read(eg)
        dispatch.note_read(ex)
        gv = self._decayed_grad(p, g._value.astype(jnp.float32))
        rho, eps = self._rho, self._epsilon
        new_eg = rho * eg._value + (1 - rho) * gv * gv
        delta = jnp.sqrt((ex._value + eps) / (new_eg + eps)) * gv
        new_ex = rho * ex._value + (1 - rho) * delta * delta
        eg._set_value(new_eg)
        ex._set_value(new_ex)
        self._write_param(
            p, (p._value.astype(jnp.float32) - lr * delta)
            .astype(p._value.dtype))
