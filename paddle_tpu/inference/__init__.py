"""Inference serving surface.

Reference: paddle/fluid/inference/api/analysis_predictor.h:94
(AnalysisPredictor: load program -> IR pass pipeline -> NaiveExecutor,
zero-copy input/output tensors) and python/paddle/inference/wrapper.py
(Config / Predictor / create_predictor).

TPU-native redesign: the "inference program" is a serialized StableHLO
executable (jit.save / jax.export).  The Predictor loads it, binds named
input handles, and runs the compiled program — XLA took the place of the
Analyzer's 200+ IR passes, and "zero copy" is the natural mode (device
arrays are handed to the executable without staging).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..tensor import Tensor as _FrameworkTensor

__all__ = [
    "Config", "Predictor", "Tensor", "create_predictor",
    "DataType", "PlaceType", "PrecisionType", "get_version",
    "get_num_bytes_of_data_type", "PredictorPool",
]


class DataType:
    FLOAT32 = "float32"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    INT32 = "int32"
    INT64 = "int64"
    INT8 = "int8"
    UINT8 = "uint8"
    BOOL = "bool"


class PlaceType:
    CPU = "cpu"
    GPU = "tpu"  # the accelerator in this build is the TPU
    TPU = "tpu"


class PrecisionType:
    Float32 = "fp32"
    Bfloat16 = "bf16"
    Half = "fp16"
    Int8 = "int8"


class Config:
    """reference wrapper.py Config / analysis_config.h: model path +
    runtime knobs.  XLA owns the optimization pipeline, so pass toggles
    are accepted for API parity and recorded into ``summary()``."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # jit.save writes a single <path> prefix; accept either the prefix
        # or the reference's (prog, params) pair pointing at it
        self._model_prefix = prog_file
        self._use_tpu = True
        self._device_id = 0
        self._enable_memory_optim = True
        self._switches: Dict[str, object] = {}
        self._causal_lm_model = None
        self._decode_opts: Optional[Dict[str, object]] = None
        self._serving_opts: Optional[Dict[str, object]] = None
        # ONE ServingEngine (and page pool) per Config, shared by every
        # Predictor created from it — the reference PredictorPool contract
        # ("N predictors sharing one program"), paged edition
        self._serving_engine = None
        self._serving_lock = __import__("threading").Lock()

    def set_model(self, prog_file, params_file=None):
        self._model_prefix = prog_file

    # -- causal-LM decode mode --------------------------------------------
    def set_causal_lm_model(self, model):
        """Serve a LIVE causal-LM (a model exposing ``generate()``) instead
        of a saved static-shape program.  A saved StableHLO artifact cannot
        run the autoregressive loop (its programs are single static calls);
        the live model's decode engine compiles exactly two programs
        (prefill + decode) and reuses them across every ``run()``."""
        self._causal_lm_model = model
        return self

    def enable_causal_lm_decode(self, max_new_tokens: int = 32,
                                do_sample: bool = False,
                                temperature: float = 1.0, top_k: int = 0,
                                top_p: Optional[float] = None,
                                eos_token_id: Optional[int] = None,
                                max_seq_len: Optional[int] = None,
                                cache_dtype: str = "bfloat16"):
        """Switch ``Predictor.run`` to autoregressive decode: input handle
        x0 takes int64 prompt ids [B, S0]; output handle out0 returns
        [B, S0 + max_new_tokens] generated ids."""
        if self._serving_opts is not None:
            raise RuntimeError(
                "enable_causal_lm_decode and enable_serving_mode are "
                "mutually exclusive — pick the single-shot decode path or "
                "the paged continuous-batching engine")
        self._decode_opts = dict(
            max_new_tokens=int(max_new_tokens), do_sample=bool(do_sample),
            temperature=float(temperature), top_k=int(top_k), top_p=top_p,
            eos_token_id=eos_token_id, max_seq_len=max_seq_len,
            cache_dtype=str(cache_dtype))
        return self

    def causal_lm_decode_enabled(self) -> bool:
        return self._decode_opts is not None

    def enable_serving_mode(self, max_new_tokens: int = 32,
                            num_slots: int = 4, page_size: int = 128,
                            max_context: Optional[int] = None,
                            num_pages: Optional[int] = None,
                            cache_dtype: str = "bfloat16",
                            prefill_chunk: Optional[int] = None,
                            do_sample: bool = False,
                            temperature: float = 1.0, top_k: int = 0,
                            top_p: float = 1.0,
                            eos_token_id: Optional[int] = None,
                            deadline_s: Optional[float] = None,
                            max_queue_depth: Optional[int] = None,
                            max_queue_wait_s: Optional[float] = None,
                            stall_budget_s: Optional[float] = None):
        """Switch ``Predictor.run`` to the continuous-batching serving
        engine (paged KV cache; docs/serving.md): each prompt row becomes
        a request through the SHARED engine, so concurrent predictors
        batch against each other instead of serializing whole generate()
        calls.  Mutually exclusive with ``enable_causal_lm_decode`` (the
        single-shot contiguous-cache path).

        Fault-containment knobs pass straight through to the engine:
        ``deadline_s`` bounds each request's lifetime, ``max_queue_depth``
        / ``max_queue_wait_s`` shed load with the typed
        ``serving.Overloaded`` error, ``stall_budget_s`` arms the step
        watchdog.  A request that ends CANCELLED / TIMED_OUT / FAILED
        surfaces from ``Predictor.run`` as the typed serving error
        attached to it (docs/serving.md "Failure model & SLOs")."""
        if self._decode_opts is not None:
            raise RuntimeError(
                "enable_serving_mode and enable_causal_lm_decode are "
                "mutually exclusive — pick the paged continuous-batching "
                "engine or the single-shot decode path")
        self._serving_opts = dict(
            max_new_tokens=int(max_new_tokens), num_slots=int(num_slots),
            page_size=int(page_size), max_context=max_context,
            num_pages=num_pages, cache_dtype=str(cache_dtype),
            prefill_chunk=prefill_chunk, do_sample=bool(do_sample),
            temperature=float(temperature), top_k=int(top_k),
            top_p=float(top_p), eos_token_id=eos_token_id,
            deadline_s=deadline_s, max_queue_depth=max_queue_depth,
            max_queue_wait_s=max_queue_wait_s,
            stall_budget_s=stall_budget_s)
        return self

    def serving_mode_enabled(self) -> bool:
        return self._serving_opts is not None

    def _get_serving_engine(self):
        """The Config-shared ServingEngine, built on first use."""
        with self._serving_lock:
            if self._serving_engine is None:
                from ..serving import ServingEngine

                o = self._serving_opts
                self._serving_engine = ServingEngine(
                    self._causal_lm_model, num_slots=o["num_slots"],
                    page_size=o["page_size"], max_context=o["max_context"],
                    num_pages=o["num_pages"], cache_dtype=o["cache_dtype"],
                    prefill_chunk=o["prefill_chunk"],
                    max_queue_depth=o.get("max_queue_depth"),
                    max_queue_wait_s=o.get("max_queue_wait_s"),
                    stall_budget_s=o.get("stall_budget_s"))
            return self._serving_engine

    def model_dir(self):
        return self._model_prefix

    def prog_file(self):
        return self._model_prefix

    # device selection (reference enable_use_gpu / disable_gpu)
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_tpu = True
        self._device_id = device_id

    def disable_gpu(self):
        self._use_tpu = False

    def use_gpu(self):
        return self._use_tpu

    def _note_inert(self, knob, value):
        """One-time (per knob) notice: the switch is recorded for API
        parity but has no effect on XLA — nothing is silently ignored
        without a trace (round-3 weak #9)."""
        if knob not in self._switches:
            import sys

            sys.stderr.write(
                f"[paddle_tpu.inference] Config.{knob}={value!r} accepted; "
                "inert on XLA/TPU (the compiler owns this decision)\n")
        self._switches[knob] = value

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag
        self._note_inert("memory_optim", flag)

    def switch_ir_optim(self, flag=True):
        self._note_inert("ir_optim", flag)  # XLA always optimizes

    def switch_use_feed_fetch_ops(self, flag=False):
        self._note_inert("feed_fetch", flag)

    def set_cpu_math_library_num_threads(self, n):
        self._note_inert("cpu_threads", n)

    def summary(self):
        lines = [f"model: {self._model_prefix}",
                 f"device: {'tpu' if self._use_tpu else 'cpu'}:{self._device_id}",
                 "compiler: XLA (StableHLO program from jit.save)"]
        if self._decode_opts is not None:
            lines.append(f"causal_lm_decode: {self._decode_opts}")
        if self._serving_opts is not None:
            lines.append(f"serving_mode: {self._serving_opts}")
        lines += [f"{k}: {v}" for k, v in self._switches.items()]
        return "\n".join(lines)


class Tensor:
    """Named IO handle (reference wrapper.py Tensor / zero-copy tensor):
    copy_from_cpu binds, copy_to_cpu fetches."""

    def __init__(self, name: str, owner: "Predictor"):
        self._name = name
        self._owner = owner

    def name(self):
        return self._name

    def copy_from_cpu(self, data):
        self._owner._inputs[self._name] = np.asarray(data)

    def share_external_data(self, tensor):
        v = tensor._value if isinstance(tensor, _FrameworkTensor) else tensor
        self._owner._inputs[self._name] = v  # zero-copy: device array as-is

    def copy_to_cpu(self):
        return np.asarray(self._owner._outputs[self._name])

    def shape(self):
        v = (self._owner._outputs.get(self._name)
             if self._name in self._owner._outputs
             else self._owner._inputs.get(self._name))
        return list(np.asarray(v).shape) if v is not None else None


class Predictor:
    """reference analysis_predictor.h:94 — but execution is one compiled
    XLA call (ZeroCopyRun -> jitted program)."""

    def __init__(self, config: Config):
        self._config = config
        self._causal_lm = config._causal_lm_model
        if ((config.causal_lm_decode_enabled()
             or config.serving_mode_enabled())
                and self._causal_lm is None):
            raise RuntimeError(
                "enable_causal_lm_decode()/enable_serving_mode() need a "
                "live model: saved StableHLO programs are single "
                "static-shape calls and cannot run the autoregressive "
                "loop; attach the model with "
                "Config.set_causal_lm_model(model)")
        if (self._causal_lm is not None
                and not config.causal_lm_decode_enabled()
                and not config.serving_mode_enabled()):
            raise RuntimeError(
                "set_causal_lm_model() without enable_causal_lm_decode() "
                "or enable_serving_mode(): decode options must be chosen "
                "explicitly (max_new_tokens, sampling, cache dtype) — "
                "call one of them before create_predictor")
        if self._causal_lm is not None:
            if not hasattr(self._causal_lm, "generate"):
                raise RuntimeError(
                    "set_causal_lm_model expects a model with generate() "
                    "(GenerationMixin)")
            self._layer = None
            self._n_inputs = 1
        else:
            from ..jit.save_load import load as _load

            self._layer = _load(config.prog_file())
            self._n_inputs = getattr(self._layer, "n_inputs", None)
            if self._n_inputs is None:
                raise RuntimeError(
                    "cannot determine the model's input arity from "
                    f"'{config.prog_file()}': the artifact predates jit.save's "
                    "n_inputs field and the exported program did not expose its "
                    "calling convention; re-save the model with jit.save")
        self._input_names = [f"x{i}" for i in range(self._n_inputs)]
        self._inputs: Dict[str, object] = {}
        self._outputs: Dict[str, object] = {}
        self._output_names: List[str] = []

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        return Tensor(name, self)

    def get_output_names(self):
        return list(self._output_names)

    def get_output_handle(self, name: str) -> Tensor:
        return Tensor(name, self)

    def run(self, inputs: Optional[list] = None):
        import contextlib

        import jax

        from ..tensor import to_tensor

        if inputs is not None:
            for i, a in enumerate(inputs):
                self._inputs[f"x{i}"] = np.asarray(
                    a._value if isinstance(a, _FrameworkTensor) else a)
        args = [to_tensor(self._inputs[k])
                for k in sorted(self._inputs, key=lambda s: int(s[1:]))]
        # device selection is REAL: Config.disable_gpu() pins execution to
        # the host CPU backend (reference enable_use_gpu/disable_gpu)
        if not self._config.use_gpu():
            try:
                ctx = jax.default_device(jax.devices("cpu")[0])
            except RuntimeError:
                ctx = contextlib.nullcontext()
        else:
            ctx = contextlib.nullcontext()
        with ctx:
            if self._config.serving_mode_enabled():
                out = self._run_serving(args[0])
            elif self._causal_lm is not None:
                opts = self._config._decode_opts or {}
                out = self._causal_lm.generate(args[0], **opts)
            else:
                out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._output_names = [f"out{i}" for i in range(len(outs))]
        self._outputs = {n: o._value for n, o in zip(self._output_names, outs)}
        if inputs is not None:
            return [_FrameworkTensor(v) for v in self._outputs.values()]
        return True

    def _run_serving(self, ids):
        """Serving mode: each prompt row becomes a request through the
        Config-shared continuous-batching engine; this thread steps the
        engine until ITS requests reach a TERMINAL state (other
        predictors' requests ride in the same batched step).  Rows that
        stop early on eos are padded with the eos id — the generate()
        output convention.  A row that ends CANCELLED / TIMED_OUT /
        FAILED re-raises its typed serving error here; an over-full
        bounded queue raises ``serving.Overloaded`` straight from
        submit (load shed — the client backs off)."""
        o = self._config._serving_opts
        eng = self._config._get_serving_engine()
        from ..serving import RequestState, SamplingParams, ServingError

        sp = SamplingParams(do_sample=o["do_sample"],
                            temperature=o["temperature"],
                            top_k=o["top_k"], top_p=o["top_p"])
        prompts = np.asarray(
            ids._value if isinstance(ids, _FrameworkTensor) else ids,
            np.int64)
        if prompts.ndim == 1:
            prompts = prompts[None, :]
        reqs = []
        try:
            for row in prompts:
                reqs.append(eng.submit(row, o["max_new_tokens"], sampling=sp,
                                       eos_token_id=o["eos_token_id"],
                                       deadline_s=o.get("deadline_s")))
        except Exception:
            # a mid-batch shed (Overloaded) must not strand the rows
            # already queued in the SHARED engine: cancel them and step
            # once so the reap retires them before re-raising
            for r in reqs:
                r.cancel()
            if reqs:
                eng.step()
            raise
        while not all(r.terminal for r in reqs):
            eng.step()
        bad = [r for r in reqs if r.state != RequestState.DONE]
        if bad:
            detail = "; ".join(
                f"row {i}: {r.state}"
                f" ({type(r.error).__name__}: {r.error})" if r.error
                else f"row {i}: {r.state}"
                for i, r in enumerate(reqs) if r.state != RequestState.DONE)
            first = bad[0]
            if len(bad) == 1 and isinstance(first.error, ServingError):
                raise first.error      # the typed terminal cause, verbatim
            raise ServingError(
                f"{len(bad)}/{len(reqs)} serving request(s) did not "
                f"complete: {detail}") from first.error
        n = o["max_new_tokens"]
        out = np.empty((len(reqs), prompts.shape[1] + n), np.int64)
        for i, r in enumerate(reqs):
            toks = list(r.tokens)
            pad = r.eos_token_id if r.eos_token_id is not None else 0
            toks += [pad] * (n - len(toks))
            out[i] = np.concatenate([r.prompt, np.asarray(toks, np.int64)])
        from ..tensor import to_tensor

        return to_tensor(out, dtype="int64")

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def get_version():
    from ..version import __version__

    return __version__


def get_num_bytes_of_data_type(dtype) -> int:
    return int(np.dtype(str(dtype)).itemsize)


class PredictorPool:
    """reference api PredictorPool: N predictors sharing one program.

    Sharing semantics (docs/decoding.md "PredictorPool and threads"):
    every predictor wraps the SAME Config — one live model, one decode
    engine cache / serving engine.  A single Predictor is NOT safe for
    concurrent ``run()`` (its input/output handle dicts are per-call
    state); distinct predictors are.  ``acquire``/``release`` hand out
    exclusive predictors with that guarantee; ``retrive(idx)`` remains
    the reference's unmanaged accessor — callers indexing the same slot
    from two threads get the races they ask for."""

    def __init__(self, config: Config, size: int = 1):
        import queue as _queue
        import threading as _threading

        if size < 1:
            raise ValueError(f"PredictorPool size must be >= 1, got {size}")
        self._predictors = [Predictor(config) for _ in range(size)]
        self._free: "_queue.Queue[Predictor]" = _queue.Queue()
        for p in self._predictors:
            self._free.put(p)
        self._out_lock = _threading.Lock()
        self._out: set = set()

    @property
    def size(self) -> int:
        return len(self._predictors)

    def retrive(self, idx: int) -> Predictor:  # (sic) reference spelling
        return self._predictors[idx]

    retrieve = retrive

    def acquire(self, timeout: Optional[float] = None) -> Predictor:
        """Exclusive predictor; blocks until one is free.  Pair with
        ``release`` (or use the ``predictor()`` context manager)."""
        import queue as _queue

        try:
            p = self._free.get(timeout=timeout)
        except _queue.Empty:
            raise TimeoutError(
                f"no free predictor after {timeout}s (pool size "
                f"{len(self._predictors)})") from None
        with self._out_lock:
            self._out.add(id(p))
        return p

    def release(self, predictor: Predictor):
        with self._out_lock:
            if id(predictor) not in self._out:
                raise ValueError(
                    "release() of a predictor that is not checked out "
                    "(double release, or not from acquire())")
            self._out.discard(id(predictor))
        self._free.put(predictor)

    def predictor(self, timeout: Optional[float] = None):
        """``with pool.predictor() as p: p.run(...)``"""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            p = self.acquire(timeout=timeout)
            try:
                yield p
            finally:
                self.release(p)

        return _ctx()
