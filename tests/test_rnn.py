"""Recurrent layers: cells vs numpy loops, multi-layer/bidirectional scan,
sequence-length masking, gradients, jit parity.

Reference test analog: /root/reference/test/rnn/test_rnn_nets.py (numpy
reference cells in /root/reference/test/rnn/rnn_numpy.py).
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _np_lstm_step(x, h, c, w_ih, w_hh, b_ih, b_hh):
    g = x @ w_ih.T + h @ w_hh.T + b_ih + b_hh
    H = h.shape[-1]
    i, f, gg, o = (g[..., :H], g[..., H:2 * H], g[..., 2 * H:3 * H],
                   g[..., 3 * H:])
    i, f, o = _sigmoid(i), _sigmoid(f), _sigmoid(o)
    c2 = f * c + i * np.tanh(gg)
    h2 = o * np.tanh(c2)
    return h2, c2


def _np_gru_step(x, h, w_ih, w_hh, b_ih, b_hh):
    H = h.shape[-1]
    gx = x @ w_ih.T + b_ih
    gh = h @ w_hh.T + b_hh
    r = _sigmoid(gx[..., :H] + gh[..., :H])
    z = _sigmoid(gx[..., H:2 * H] + gh[..., H:2 * H])
    c = np.tanh(gx[..., 2 * H:] + r * gh[..., 2 * H:])
    return z * h + (1.0 - z) * c


def test_lstm_cell_matches_numpy():
    pt.seed(0)
    cell = nn.LSTMCell(4, 6)
    x = pt.to_tensor(np.random.RandomState(0).randn(3, 4).astype("float32"))
    out, (h, c) = cell(x)
    w_ih = np.asarray(cell.weight_ih.numpy())
    w_hh = np.asarray(cell.weight_hh.numpy())
    b_ih = np.asarray(cell.bias_ih.numpy())
    b_hh = np.asarray(cell.bias_hh.numpy())
    h_ref, c_ref = _np_lstm_step(x.numpy(), np.zeros((3, 6), "float32"),
                                 np.zeros((3, 6), "float32"),
                                 w_ih, w_hh, b_ih, b_hh)
    np.testing.assert_allclose(h.numpy(), h_ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(c.numpy(), c_ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(out.numpy(), h_ref, rtol=1e-5, atol=1e-5)


def test_gru_cell_matches_numpy():
    pt.seed(1)
    cell = nn.GRUCell(5, 3)
    x = pt.to_tensor(np.random.RandomState(1).randn(2, 5).astype("float32"))
    h0 = pt.to_tensor(np.random.RandomState(2).randn(2, 3).astype("float32"))
    out, h = cell(x, h0)
    ref = _np_gru_step(x.numpy(), h0.numpy(),
                       np.asarray(cell.weight_ih.numpy()),
                       np.asarray(cell.weight_hh.numpy()),
                       np.asarray(cell.bias_ih.numpy()),
                       np.asarray(cell.bias_hh.numpy()))
    np.testing.assert_allclose(h.numpy(), ref, rtol=1e-5, atol=1e-5)


def test_lstm_layer_matches_manual_cell_loop():
    pt.seed(2)
    B, T, I, H = 2, 5, 4, 6
    lstm = nn.LSTM(I, H, num_layers=1)
    x_np = np.random.RandomState(3).randn(B, T, I).astype("float32")
    out, (h, c) = lstm(pt.to_tensor(x_np))
    assert tuple(out.shape) == (B, T, H)
    assert tuple(h.shape) == (1, B, H) and tuple(c.shape) == (1, B, H)

    cell = lstm._cells[0]
    hh = np.zeros((B, H), "float32")
    cc = np.zeros((B, H), "float32")
    w = [np.asarray(p.numpy()) for p in
         (cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh)]
    outs = []
    for t in range(T):
        hh, cc = _np_lstm_step(x_np[:, t], hh, cc, *w)
        outs.append(hh)
    np.testing.assert_allclose(out.numpy(), np.stack(outs, 1),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(h.numpy()[0], hh, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(c.numpy()[0], cc, rtol=1e-4, atol=1e-4)


def test_bidirectional_multilayer_shapes():
    pt.seed(3)
    gru = nn.GRU(4, 5, num_layers=2, direction="bidirect")
    x = pt.to_tensor(np.random.RandomState(4).randn(3, 7, 4).astype("float32"))
    out, h = gru(x)
    assert tuple(out.shape) == (3, 7, 10)
    assert tuple(h.shape) == (4, 3, 5)  # num_layers * num_directions


def test_sequence_length_masking():
    pt.seed(4)
    rnn = nn.SimpleRNN(3, 4)
    B, T = 2, 6
    x_np = np.random.RandomState(5).randn(B, T, 3).astype("float32")
    seq = pt.to_tensor(np.array([4, 6], "int64"))
    out, h = rnn(pt.to_tensor(x_np), sequence_length=seq)
    out_np = out.numpy()
    # steps past the end emit zeros
    np.testing.assert_allclose(out_np[0, 4:], 0.0)
    # final state for row 0 equals output at its last valid step
    np.testing.assert_allclose(h.numpy()[0, 0], out_np[0, 3],
                               rtol=1e-5, atol=1e-5)
    # full-length row matches the unmasked run
    out_full, _ = rnn(pt.to_tensor(x_np))
    np.testing.assert_allclose(out_np[1], out_full.numpy()[1],
                               rtol=1e-5, atol=1e-5)


def test_reverse_rnn_wrapper():
    pt.seed(5)
    cell = nn.SimpleRNNCell(3, 4)
    wrapper = nn.RNN(cell, is_reverse=True)
    x_np = np.random.RandomState(6).randn(2, 5, 3).astype("float32")
    out, h = wrapper(pt.to_tensor(x_np))
    # reversed scan: final state corresponds to t=0 output
    np.testing.assert_allclose(h.numpy(), out.numpy()[:, 0],
                               rtol=1e-5, atol=1e-5)

    birnn = nn.BiRNN(nn.SimpleRNNCell(3, 4), nn.SimpleRNNCell(3, 4))
    out2, (hf, hb) = birnn(pt.to_tensor(x_np))
    assert tuple(out2.shape) == (2, 5, 8)


def test_lstm_gradients_flow():
    pt.seed(6)
    lstm = nn.LSTM(4, 4, num_layers=2, direction="bidirect")
    x = pt.to_tensor(np.random.RandomState(7).randn(2, 5, 4).astype("float32"))
    out, _ = lstm(x)
    loss = out.sum()
    loss.backward()
    for p in lstm.parameters():
        assert p.grad is not None, p.name
        assert np.isfinite(p.grad.numpy()).all()


def test_lstm_jit_parity():
    pt.seed(7)
    lstm = nn.LSTM(4, 6)
    lstm.eval()
    x = pt.to_tensor(np.random.RandomState(8).randn(2, 5, 4).astype("float32"))
    with pt.no_grad():
        eager, _ = lstm(x)

    @pt.jit.to_static
    def run(x):
        with pt.no_grad():
            out, _ = lstm(x)
        return out

    compiled = run(x)
    compiled2 = run(x)
    np.testing.assert_allclose(eager.numpy(), compiled.numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(compiled.numpy(), compiled2.numpy(),
                               rtol=1e-6, atol=1e-6)
