"""First-party Pallas TPU kernels + wrappers over jax's pallas op library.

The reference keeps its hot ops as handwritten CUDA
(paddle/phi/kernels/fusion/, operators/fused/); here the hot ops are
Pallas kernels compiled through Mosaic for the TPU's MXU/VMEM.
"""
from . import decode_attention  # noqa: F401  (module: decode_attention.decode_attention)
from . import paged_attention  # noqa: F401  (module: paged_attention.paged_attention)
from .rms_norm import fused_add_layer_norm, fused_add_rms_norm  # noqa: F401
