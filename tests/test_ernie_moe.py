"""ErnieMoE model family — BASELINE config 4 (ERNIE-MoE expert-parallel
trains end-to-end; reference incubate/distributed/models/moe)."""
import numpy as np
import jax
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import mesh as M
from paddle_tpu.models import ErnieMoEForPretraining, ernie_moe_tiny


def _batch(cfg, b=2, s=16):
    rng = np.random.RandomState(0)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (b, s)),
                       dtype="int64")
    labels = pt.to_tensor(rng.randint(0, cfg.vocab_size, (b, s)),
                          dtype="int64")
    return ids, labels


def test_ernie_moe_trains_compiled():
    pt.seed(0)
    cfg = ernie_moe_tiny(hidden_dropout=0.0, attention_dropout=0.0,
                         num_layers=2, hidden_size=32)
    m = ErnieMoEForPretraining(cfg)
    # alternating dense/MoE blocks
    assert [b.is_moe for b in m.ernie.blocks] == [False, True]
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=m.parameters())
    ids, labels = _batch(cfg)

    @pt.jit.to_static
    def step(ids, labels):
        loss = m(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    losses = [float(step(ids, labels)) for _ in range(5)]
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]
    # the aux (balance) loss is wired into the total
    m(ids, labels=labels)
    assert m.ernie.moe_aux_loss() is not None
    assert float(m.ernie.moe_aux_loss()) > 0


def test_ernie_moe_recompute_matches():
    """recompute_interval is honored (same loss, remat on)."""
    cfg0 = ernie_moe_tiny(hidden_dropout=0.0, attention_dropout=0.0,
                          num_layers=2, hidden_size=32)
    pt.seed(3)
    m0 = ErnieMoEForPretraining(cfg0)
    cfg1 = ernie_moe_tiny(hidden_dropout=0.0, attention_dropout=0.0,
                          num_layers=2, hidden_size=32,
                          recompute_interval=1)
    pt.seed(3)
    m1 = ErnieMoEForPretraining(cfg1)
    ids, labels = _batch(cfg0)
    l0 = m0(ids, labels=labels)
    l1 = m1(ids, labels=labels)
    l0.backward()
    l1.backward()
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-5)


@pytest.mark.slow
def test_ernie_moe_expert_parallel_alltoall():
    """config-4 shape: expert parallelism over an ep mesh axis with the
    explicit all_to_all dispatch."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    prev = M._global_mesh
    try:
        M.set_mesh(M.build_mesh({"dp": 2, "ep": 4}))
        pt.seed(0)
        cfg = ernie_moe_tiny(hidden_dropout=0.0, attention_dropout=0.0,
                             num_experts=8, dispatch_mode="alltoall")
        m = ErnieMoEForPretraining(cfg)
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters())
        ids, labels = _batch(cfg, b=8)

        from paddle_tpu.ops.sharding_ops import shard_constraint

        @pt.jit.to_static
        def step(ids, labels):
            ids2 = shard_constraint(ids, "ep", None)
            lab2 = shard_constraint(labels, "ep", None)
            loss = m(ids2, labels=lab2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        losses = [float(step(ids, labels)) for _ in range(3)]
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]
    finally:
        M._global_mesh = prev
